"""NodeProvider plugin API + the fake in-process provider.

Reference: python/ray/autoscaler/node_provider.py (the cloud plugin
surface) and autoscaler/_private/fake_multi_node/node_provider.py:225
(FakeMultiNodeProvider — "launches" nodes into the local cluster so the
full reconcile loop runs without a cloud).
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional

TAG_NODE_KIND = "ray-node-kind"
TAG_USER_NODE_TYPE = "ray-user-node-type"
TAG_NODE_STATUS = "ray-node-status"
NODE_KIND_HEAD = "head"
NODE_KIND_WORKER = "worker"
STATUS_UP_TO_DATE = "up-to-date"


class NodeProvider:
    """Cloud plugin interface (subset the autoscaler core needs)."""

    def __init__(self, provider_config: Dict[str, Any], cluster_name: str):
        self.provider_config = provider_config
        self.cluster_name = cluster_name

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        raise NotImplementedError

    def is_running(self, node_id: str) -> bool:
        raise NotImplementedError

    def node_tags(self, node_id: str) -> Dict[str, str]:
        raise NotImplementedError

    def internal_ip(self, node_id: str) -> str:
        raise NotImplementedError

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> None:
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError


class FakeMultiNodeProvider(NodeProvider):
    """Launches "nodes" straight into the in-process runtime: create_node
    calls runtime.add_node with the node type's resources; terminate_node
    removes the raylet (which exercises actor restart / object loss the
    same way a real node death does)."""

    def __init__(self, provider_config: Dict[str, Any],
                 cluster_name: str = "fake", runtime=None):
        super().__init__(provider_config, cluster_name)
        from ray_tpu.core import runtime as rt_mod

        self._runtime = runtime or rt_mod.global_runtime
        if self._runtime is None:
            raise RuntimeError("FakeMultiNodeProvider needs ray_tpu.init()")
        self._lock = threading.Lock()
        # provider node id -> (tags, raylet NodeID)
        self._nodes: Dict[str, Dict[str, Any]] = {}
        head_id = f"fake-head-{uuid.uuid4().hex[:8]}"
        self._nodes[head_id] = {
            "tags": {TAG_NODE_KIND: NODE_KIND_HEAD,
                     TAG_NODE_STATUS: STATUS_UP_TO_DATE,
                     TAG_USER_NODE_TYPE: provider_config.get(
                         "head_node_type", "head")},
            "node_id": self._runtime.head_raylet.node_id,
        }

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        with self._lock:
            out = []
            for nid, info in self._nodes.items():
                if all(info["tags"].get(k) == v
                       for k, v in tag_filters.items()):
                    out.append(nid)
            return out

    def is_running(self, node_id: str) -> bool:
        with self._lock:
            return node_id in self._nodes

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            return dict(self._nodes[node_id]["tags"])

    def internal_ip(self, node_id: str) -> str:
        return node_id

    def raylet_node_id(self, node_id: str):
        with self._lock:
            return self._nodes[node_id]["node_id"]

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> None:
        resources = dict(node_config.get("resources", {"CPU": 1}))
        for _ in range(count):
            raylet = self._runtime.add_node(dict(resources))
            nid = f"fake-{uuid.uuid4().hex[:8]}"
            with self._lock:
                self._nodes[nid] = {
                    "tags": {**tags, TAG_NODE_STATUS: STATUS_UP_TO_DATE},
                    "node_id": raylet.node_id,
                }

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            info = self._nodes.pop(node_id, None)
        if info is not None and info["tags"].get(
                TAG_NODE_KIND) != NODE_KIND_HEAD:
            self._runtime.remove_node(info["node_id"])


class ClusterNodeProvider(NodeProvider):
    """Backs the autoscaler with a live ProcessCluster: create_node spawns
    a real raylet process, terminate_node drains it through the GCS before
    stopping it (ProcessCluster.remove_node), and externally-killed nodes
    (preemption storms) fall out of non_terminated_nodes on the next poll
    so the reconcile loop replaces the lost capacity.

    Provider node ids ARE raylet node ids — raylet_node_id is the
    identity, and exposing ``gcs_address`` routes
    StandardAutoscaler.update through LoadMetrics.update_from_gcs (demand
    from real raylet queues, capacity from heartbeat-fed cluster_view).
    """

    def __init__(self, provider_config: Dict[str, Any],
                 cluster_name: str = "process", cluster=None):
        super().__init__(provider_config, cluster_name)
        if cluster is None:
            raise ValueError("ClusterNodeProvider needs a ProcessCluster")
        self._cluster = cluster
        self._lock = threading.Lock()
        self._default_type = provider_config.get("worker_node_type",
                                                 "worker")
        self._tags: Dict[str, Dict[str, str]] = {}
        self._reconcile()

    @property
    def gcs_address(self) -> str:
        return self._cluster.gcs_address

    def _reconcile(self) -> None:
        """Sync the tag table with the cluster's real process set: adopt
        raylets launched outside the provider, drop ones whose process is
        gone (preempted / hard-killed / drained away)."""
        with self._lock:
            procs = dict(self._cluster.raylets)
            for node_id in list(self._tags):
                proc = procs.get(node_id)
                if proc is None or proc.poll() is not None:
                    del self._tags[node_id]
            for node_id, proc in procs.items():
                if proc.poll() is None and node_id not in self._tags:
                    self._tags[node_id] = {
                        TAG_NODE_KIND: NODE_KIND_WORKER,
                        TAG_NODE_STATUS: STATUS_UP_TO_DATE,
                        TAG_USER_NODE_TYPE: self._default_type,
                    }

    def non_terminated_nodes(self, tag_filters: Dict[str, str]) -> List[str]:
        self._reconcile()
        with self._lock:
            return [nid for nid, tags in self._tags.items()
                    if all(tags.get(k) == v for k, v in tag_filters.items())]

    def is_running(self, node_id: str) -> bool:
        proc = self._cluster.raylets.get(node_id)
        return proc is not None and proc.poll() is None

    def node_tags(self, node_id: str) -> Dict[str, str]:
        with self._lock:
            return dict(self._tags.get(node_id, {}))

    def internal_ip(self, node_id: str) -> str:
        return self._cluster.node_addresses.get(node_id, node_id)

    def raylet_node_id(self, node_id: str) -> str:
        return node_id  # provider ids are raylet ids

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> None:
        resources = dict(node_config.get("resources", {"CPU": 1}))
        num_cpus = float(resources.get("CPU", 1.0))
        for _ in range(count):
            node_id = self._cluster.add_node(num_cpus=num_cpus,
                                             resources=dict(resources))
            with self._lock:
                self._tags[node_id] = {
                    **tags, TAG_NODE_STATUS: STATUS_UP_TO_DATE}

    def terminate_node(self, node_id: str) -> None:
        with self._lock:
            info = self._tags.pop(node_id, None)
        if info is not None and info.get(TAG_NODE_KIND) != NODE_KIND_HEAD:
            # graceful path: GCS drain (actors migrate, sole-copy objects
            # re-replicate) before the process stops
            self._cluster.remove_node(node_id)
