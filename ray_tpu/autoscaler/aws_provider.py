"""AWS-style cloud node provider.

Reference: python/ray/autoscaler/_private/aws/node_provider.py — the
EC2 plugin behind the NodeProvider surface: launch via
``run_instances``, discover via ``describe_instances`` with tag
filters, tag via ``create_tags``, reap via ``terminate_instances``.

The EC2 client is injected (boto3-shaped): pass ``boto3.client("ec2")``
on a real account, or :class:`FakeEC2Client` — an in-memory mock with
the create/terminate/tag/filter semantics of the real API — which the
test suite drives the full autoscaler reconcile loop against (this
image has no boto3 and zero egress; the seam is what parity requires).
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional

from ray_tpu.autoscaler.node_provider import NodeProvider


class FakeEC2Client:
    """boto3-shaped EC2 mock: instances with states, tags, private IPs,
    and describe-filters. Thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._instances: Dict[str, Dict[str, Any]] = {}
        self._ip_octet = 10

    def run_instances(self, MaxCount: int = 1, MinCount: int = 1,
                      TagSpecifications: Optional[List] = None,
                      **kwargs) -> Dict:
        tags = []
        for spec in TagSpecifications or []:
            if spec.get("ResourceType") == "instance":
                tags.extend(spec.get("Tags", []))
        out = []
        with self._lock:
            for _ in range(MaxCount):
                iid = "i-" + uuid.uuid4().hex[:17]
                self._ip_octet += 1
                inst = {
                    "InstanceId": iid,
                    "State": {"Name": "running"},
                    "PrivateIpAddress": f"10.0.0.{self._ip_octet}",
                    "Tags": [dict(t) for t in tags],
                    "InstanceType": kwargs.get("InstanceType", ""),
                }
                self._instances[iid] = inst
                out.append(inst)
        return {"Instances": out}

    def terminate_instances(self, InstanceIds: List[str]) -> Dict:
        with self._lock:
            for iid in InstanceIds:
                inst = self._instances.get(iid)
                if inst is not None:
                    inst["State"] = {"Name": "terminated"}
        return {}

    def create_tags(self, Resources: List[str], Tags: List[Dict]) -> Dict:
        with self._lock:
            for iid in Resources:
                inst = self._instances.get(iid)
                if inst is None:
                    continue
                for new in Tags:
                    for t in inst["Tags"]:
                        if t["Key"] == new["Key"]:
                            t["Value"] = new["Value"]
                            break
                    else:
                        inst["Tags"].append(dict(new))
        return {}

    def describe_instances(self, Filters: Optional[List[Dict]] = None,
                           **_) -> Dict:
        def matches(inst) -> bool:
            for f in Filters or []:
                name, values = f["Name"], f["Values"]
                if name == "instance-state-name":
                    if inst["State"]["Name"] not in values:
                        return False
                elif name.startswith("tag:"):
                    key = name[4:]
                    tag = {t["Key"]: t["Value"] for t in inst["Tags"]}
                    if tag.get(key) not in values:
                        return False
                else:
                    return False
            return True

        with self._lock:
            found = [dict(i) for i in self._instances.values()
                     if matches(i)]
        return {"Reservations": [{"Instances": found}]} if found else {
            "Reservations": []}


class AwsNodeProvider(NodeProvider):
    """EC2-backed NodeProvider. provider_config keys:

      region            informational
      _client           injected boto3-shaped client (tests/MinIO-style
                        stacks); absent -> boto3.client("ec2", region)
    """

    def __init__(self, provider_config: Dict[str, Any],
                 cluster_name: str):
        super().__init__(provider_config, cluster_name)
        client = provider_config.get("_client")
        if client is None:
            try:
                import boto3  # type: ignore
            except ImportError as e:
                raise RuntimeError(
                    "aws provider needs boto3 (or inject a "
                    "boto3-shaped client via provider._client)") from e
            client = boto3.client(
                "ec2", region_name=provider_config.get("region"))
        self.ec2 = client

    # -------------------------------------------------------------- helpers
    def _cluster_filter(self) -> List[Dict]:
        return [
            {"Name": "instance-state-name", "Values": ["pending",
                                                       "running"]},
            {"Name": "tag:ray-cluster-name",
             "Values": [self.cluster_name]},
        ]

    def _describe(self, extra: Optional[List[Dict]] = None) -> List[Dict]:
        resp = self.ec2.describe_instances(
            Filters=self._cluster_filter() + (extra or []))
        out = []
        for res in resp.get("Reservations", []):
            out.extend(res.get("Instances", []))
        return out

    def _get(self, node_id: str) -> Optional[Dict]:
        for inst in self._describe():
            if inst["InstanceId"] == node_id:
                return inst
        return None

    # ------------------------------------------------------------- surface
    def non_terminated_nodes(self, tag_filters: Dict[str, str]
                             ) -> List[str]:
        extra = [{"Name": f"tag:{k}", "Values": [v]}
                 for k, v in tag_filters.items()]
        return [i["InstanceId"] for i in self._describe(extra)]

    def is_running(self, node_id: str) -> bool:
        inst = self._get(node_id)
        return bool(inst) and inst["State"]["Name"] == "running"

    def node_tags(self, node_id: str) -> Dict[str, str]:
        inst = self._get(node_id)
        if inst is None:
            return {}
        return {t["Key"]: t["Value"] for t in inst["Tags"]}

    def set_node_tags(self, node_id: str, tags: Dict[str, str]) -> None:
        self.ec2.create_tags(
            Resources=[node_id],
            Tags=[{"Key": k, "Value": v} for k, v in tags.items()])

    def internal_ip(self, node_id: str) -> str:
        inst = self._get(node_id)
        return inst.get("PrivateIpAddress", "") if inst else ""

    def create_node(self, node_config: Dict[str, Any],
                    tags: Dict[str, str], count: int) -> None:
        all_tags = dict(tags)
        all_tags["ray-cluster-name"] = self.cluster_name
        self.ec2.run_instances(
            MaxCount=count, MinCount=count,
            InstanceType=node_config.get("InstanceType", ""),
            TagSpecifications=[{
                "ResourceType": "instance",
                "Tags": [{"Key": k, "Value": v}
                         for k, v in all_tags.items()],
            }])

    def terminate_node(self, node_id: str) -> None:
        self.ec2.terminate_instances(InstanceIds=[node_id])
