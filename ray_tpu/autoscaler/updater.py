"""Node bootstrap: turn a bare machine into a cluster node.

Reference: python/ray/autoscaler/_private/updater.py (``NodeUpdater``):
wait until the node answers a trivial command, sync file mounts, run
``initialization_commands`` then ``setup_commands`` then
``start_ray_commands``, and tag the node ``up-to-date`` on success or
``update-failed`` on any error so the autoscaler recycles it.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Optional

from ray_tpu.autoscaler.command_runner import CommandRunnerInterface
from ray_tpu.autoscaler.node_provider import (
    STATUS_UP_TO_DATE,
    TAG_NODE_STATUS,
)

logger = logging.getLogger(__name__)

STATUS_UPDATE_FAILED = "update-failed"
STATUS_WAITING_FOR_SSH = "waiting-for-ssh"
STATUS_SETTING_UP = "setting-up"


class NodeUpdaterError(RuntimeError):
    pass


class NodeUpdater:
    """Drives one node from bare to running through a CommandRunner."""

    def __init__(self, node_id: str, provider, runner: CommandRunnerInterface,
                 initialization_commands: Optional[List[str]] = None,
                 setup_commands: Optional[List[str]] = None,
                 start_commands: Optional[List[str]] = None,
                 file_mounts: Optional[Dict[str, str]] = None,
                 ready_timeout_s: float = 60.0,
                 ready_poll_s: float = 1.0):
        self.node_id = node_id
        self.provider = provider
        self.runner = runner
        self.initialization_commands = initialization_commands or []
        self.setup_commands = setup_commands or []
        self.start_commands = start_commands or []
        self.file_mounts = file_mounts or {}
        self.ready_timeout_s = ready_timeout_s
        self.ready_poll_s = ready_poll_s
        self.exit_cause: Optional[str] = None

    # ------------------------------------------------------------------ run
    def run(self) -> None:
        try:
            self._set_status(STATUS_WAITING_FOR_SSH)
            self.wait_ready()
            self._set_status(STATUS_SETTING_UP)
            self.sync_file_mounts()
            for phase, commands in (
                    ("initialization", self.initialization_commands),
                    ("setup", self.setup_commands),
                    ("start", self.start_commands)):
                for cmd in commands:
                    rc, out = self.runner.run(cmd)
                    if rc != 0:
                        raise NodeUpdaterError(
                            f"{phase} command failed rc={rc} on "
                            f"{self.node_id}: {cmd!r}\n{out}")
            self._set_status(STATUS_UP_TO_DATE)
        except BaseException as e:
            self.exit_cause = f"{type(e).__name__}: {e}"
            self._set_status(STATUS_UPDATE_FAILED)
            raise

    def wait_ready(self) -> None:
        """The node is ready when it can run a trivial command
        (reference: updater retries `uptime` until ssh answers)."""
        deadline = time.monotonic() + self.ready_timeout_s
        last = ""
        while time.monotonic() < deadline:
            try:
                rc, out = self.runner.run("true", timeout=15.0)
                if rc == 0:
                    return
                last = f"rc={rc}: {out}"
            except Exception as e:  # noqa: BLE001 — keep retrying
                last = f"{type(e).__name__}: {e}"
            time.sleep(self.ready_poll_s)
        raise NodeUpdaterError(
            f"node {self.node_id} never became reachable "
            f"({self.ready_timeout_s:.0f}s): {last}")

    def sync_file_mounts(self) -> None:
        for target, source in self.file_mounts.items():
            self.runner.run_rsync_up(source, target)

    def _set_status(self, status: str) -> None:
        set_tags = getattr(self.provider, "set_node_tags", None)
        if set_tags is not None:
            try:
                set_tags(self.node_id, {TAG_NODE_STATUS: status})
            except Exception:  # noqa: BLE001 — tags are advisory
                logger.debug("set_node_tags failed", exc_info=True)
