"""ray_tpu.autoscaler — demand-driven cluster scaling.

Reference surface: python/ray/autoscaler/ (StandardAutoscaler, the
NodeProvider plugin API, the resource-demand bin-packing scheduler, and
the fake multi-node provider for tests).
"""

from ray_tpu.autoscaler.autoscaler import Monitor, StandardAutoscaler  # noqa: F401
from ray_tpu.autoscaler.load_metrics import LoadMetrics  # noqa: F401
from ray_tpu.autoscaler.node_provider import (  # noqa: F401
    ClusterNodeProvider,
    FakeMultiNodeProvider,
    NodeProvider,
)
from ray_tpu.autoscaler.resource_demand_scheduler import (  # noqa: F401
    get_nodes_to_launch,
)
from ray_tpu.autoscaler.commands import (  # noqa: F401
    ProcessNodeProvider,
    create_or_update_cluster,
    get_head_node_ip,
    get_worker_node_ips,
    load_cluster_config,
    register_node_provider,
    teardown_cluster,
)

__all__ = [
    "StandardAutoscaler", "Monitor", "LoadMetrics", "NodeProvider",
    "FakeMultiNodeProvider", "ClusterNodeProvider", "get_nodes_to_launch",
    "ProcessNodeProvider", "create_or_update_cluster", "teardown_cluster",
    "get_head_node_ip", "get_worker_node_ips", "load_cluster_config",
    "register_node_provider",
]
