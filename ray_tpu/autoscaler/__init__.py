"""ray_tpu.autoscaler — demand-driven cluster scaling.

Reference surface: python/ray/autoscaler/ (StandardAutoscaler, the
NodeProvider plugin API, the resource-demand bin-packing scheduler, and
the fake multi-node provider for tests).
"""

from ray_tpu.autoscaler.autoscaler import Monitor, StandardAutoscaler  # noqa: F401
from ray_tpu.autoscaler.load_metrics import LoadMetrics  # noqa: F401
from ray_tpu.autoscaler.node_provider import (  # noqa: F401
    FakeMultiNodeProvider,
    NodeProvider,
)
from ray_tpu.autoscaler.resource_demand_scheduler import (  # noqa: F401
    get_nodes_to_launch,
)

__all__ = [
    "StandardAutoscaler", "Monitor", "LoadMetrics", "NodeProvider",
    "FakeMultiNodeProvider", "get_nodes_to_launch",
]
